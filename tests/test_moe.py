"""MoE op + Mixtral-family tests (CPU, 8-device virtual mesh).

Covers the routing/dispatch math in ops/moe.py against an independent
per-token reference, expert-parallel sharded parity, and EP serving
through the engine. HF numerics parity for Mixtral lives in
tests/test_model_numerics.py next to the other families.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from production_stack_tpu.models import ModelConfig, llama
from production_stack_tpu.ops import moe
from production_stack_tpu.parallel.mesh import MeshConfig, build_mesh
from production_stack_tpu.parallel.sharding import shard_params

MOE_CFG = ModelConfig(name="t-moe", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=8,
                      num_kv_heads=4, max_position_embeddings=256,
                      num_experts=4, num_experts_per_tok=2,
                      dtype=jnp.float32)


def _rand_moe(key, N=96, h=32, E=4, i=64):
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (N, h), jnp.float32)
    rw = jax.random.normal(ks[1], (h, E), jnp.float32) * 0.2
    g = jax.random.normal(ks[2], (E, h, i), jnp.float32) * 0.1
    u = jax.random.normal(ks[3], (E, h, i), jnp.float32) * 0.1
    d = jax.random.normal(ks[4], (E, i, h), jnp.float32) * 0.1
    return x, rw, g, u, d


def _reference_moe(x, rw, g, u, d, k, capacity=None, valid=None):
    """Per-token numpy loop: softmax-all, top-k, renormalize, run the
    selected experts one by one. Independent of ops/moe.py's vectorized
    dispatch. capacity simulates per-expert slots filled in token-major
    assignment order (the dispatch path's ranking); valid marks padding
    rows that contribute nothing and consume no capacity."""
    x, rw, g, u, d = map(np.asarray, (x, rw, g, u, d))
    N = x.shape[0]
    E = g.shape[0]
    out = np.zeros_like(x)
    counts = np.zeros(E, np.int64)
    for t in range(N):
        if valid is not None and not valid[t]:
            continue
        logits = x[t] @ rw
        p = np.exp(logits - logits.max())
        p /= p.sum()
        top = np.argsort(-p)[:k]
        w = p[top] / p[top].sum()
        for wi, e in zip(w, top):
            if capacity is not None:
                if counts[e] >= capacity:
                    continue          # dropped: rides the residual
                counts[e] += 1
            hidden = (x[t] @ g[e])
            hidden = hidden / (1 + np.exp(-hidden)) * (x[t] @ u[e])
            out[t] += wi * (hidden @ d[e])
    return out


def test_route_weights_normalized():
    x, rw, *_ = _rand_moe(jax.random.PRNGKey(0))
    w, idx = moe.route(x, rw, top_k=2)
    np.testing.assert_allclose(np.asarray(w).sum(-1), 1.0, atol=1e-6)
    assert np.asarray(idx).min() >= 0 and np.asarray(idx).max() < 4
    # top-k indices are distinct per token
    assert (np.asarray(idx)[:, 0] != np.asarray(idx)[:, 1]).all()


def test_exact_path_matches_reference():
    x, rw, g, u, d = _rand_moe(jax.random.PRNGKey(1))
    got = moe.moe_mlp(x, rw, g, u, d, top_k=2, dense_threshold=1000)
    np.testing.assert_allclose(np.asarray(got),
                               _reference_moe(x, rw, g, u, d, 2),
                               atol=1e-4, rtol=1e-4)


def test_dispatch_path_matches_reference():
    x, rw, g, u, d = _rand_moe(jax.random.PRNGKey(2))
    # capacity_factor 1.6 -> capacity < N (dispatch branch) but above the
    # realized max expert load for this seed, so no token is dropped
    got = moe.moe_mlp(x, rw, g, u, d, top_k=2, dense_threshold=1,
                      capacity_factor=1.6)
    cap = moe.capacity_for(x.shape[0], 4, 2, 1.6)
    assert cap < x.shape[0], "capacity must not force the exact branch"
    np.testing.assert_allclose(np.asarray(got),
                               _reference_moe(x, rw, g, u, d, 2),
                               atol=1e-4, rtol=1e-4)


def test_dispatch_with_drops_matches_reference():
    """Over-capacity assignments drop in token-major rank order — the
    numpy reference simulates the same fill and must agree exactly."""
    x, rw, g, u, d = _rand_moe(jax.random.PRNGKey(3))
    got = moe.moe_mlp(x, rw, g, u, d, top_k=2, dense_threshold=1,
                      capacity_factor=0.5)
    cap = moe.capacity_for(x.shape[0], 4, 2, 0.5)
    ref = _reference_moe(x, rw, g, u, d, 2, capacity=cap)
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), ref, atol=1e-4, rtol=1e-4)


def test_padding_never_routes_or_steals_capacity():
    """Padding rows (valid=False) contribute zero output AND consume no
    expert capacity — real tokens see the same result as if the padding
    did not exist."""
    x, rw, g, u, d = _rand_moe(jax.random.PRNGKey(6))
    N = x.shape[0]
    valid = np.zeros(N, bool)
    valid[: N // 3] = True          # 2/3 of the batch is padding
    cap = moe.capacity_for(N, 4, 2, 0.5)
    got = moe.moe_mlp(x, rw, g, u, d, top_k=2, dense_threshold=1,
                      capacity_factor=0.5, valid=jnp.asarray(valid))
    ref = _reference_moe(x, rw, g, u, d, 2, capacity=cap, valid=valid)
    np.testing.assert_allclose(np.asarray(got), ref, atol=1e-4, rtol=1e-4)
    assert (np.asarray(got)[~valid] == 0).all()
    # exact path masks padding too
    got_exact = moe.moe_mlp(x, rw, g, u, d, top_k=2, dense_threshold=1000,
                            valid=jnp.asarray(valid))
    assert (np.asarray(got_exact)[~valid] == 0).all()


def test_exact_flag_overrides_capacity():
    """exact=True (the decode path) never drops, whatever N/capacity."""
    x, rw, g, u, d = _rand_moe(jax.random.PRNGKey(7))
    got = moe.moe_mlp(x, rw, g, u, d, top_k=2, dense_threshold=1,
                      capacity_factor=0.5, exact=True)
    np.testing.assert_allclose(np.asarray(got),
                               _reference_moe(x, rw, g, u, d, 2),
                               atol=1e-4, rtol=1e-4)


def test_capacity_for():
    assert moe.capacity_for(512, 8, 2, 1.0) == 128
    assert moe.capacity_for(512, 8, 2, 100.0) == 512   # clamped to N
    assert moe.capacity_for(8, 8, 2, 1.0) == 8         # floor of 8
    assert moe.capacity_for(100, 8, 2, 1.0) % 8 == 0   # 8-aligned


def test_moe_forward_train_finite():
    params = llama.init_params(MOE_CFG, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 48), 0,
                              MOE_CFG.vocab_size)
    logits = llama.forward_train(params, MOE_CFG, toks)
    assert logits.shape == (2, 48, MOE_CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_ep_sharded_forward_matches_single_device():
    """ep=4 x tp=2 mesh: expert weights shard over ep, logits must match
    the unsharded forward exactly (no drops at these sizes: N=32 tokens
    stay on the exact all-expert path)."""
    mesh = build_mesh(MeshConfig(dp=1, sp=1, ep=4, tp=2))
    params = llama.init_params(MOE_CFG, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                              MOE_CFG.vocab_size)

    expected = llama.forward_train(params, MOE_CFG, toks)
    sharded = shard_params(mesh, params)
    got = jax.jit(lambda p, t: llama.forward_train(p, MOE_CFG, t))(
        sharded, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-4, rtol=2e-4)


def test_ep_serving_engine_matches_unsharded():
    """Greedy generation through the engine: identical output with and
    without an ep=2 serving mesh on the debug-moe preset."""
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.scheduler import SamplingOptions

    opts = SamplingOptions(temperature=0.0, max_tokens=8)
    base = EngineConfig(model="debug-moe", max_model_len=128,
                        max_num_seqs=2, prefill_chunk=32,
                        prefill_buckets=(16, 32))
    plain = LLMEngine(base).generate("expert parallel probe", opts)

    ep_cfg = EngineConfig(model="debug-moe", max_model_len=128,
                          max_num_seqs=2, prefill_chunk=32,
                          prefill_buckets=(16, 32),
                          expert_parallel_size=2)
    sharded = LLMEngine(ep_cfg).generate("expert parallel probe", opts)
    assert plain == sharded


def test_ep_validation():
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine

    with pytest.raises(ValueError, match="dense"):
        LLMEngine(EngineConfig(model="debug-tiny", max_model_len=64,
                               expert_parallel_size=2))
    with pytest.raises(ValueError, match="divide"):
        LLMEngine(EngineConfig(model="debug-moe", max_model_len=64,
                               expert_parallel_size=3))


def test_lora_mlp_targets_rejected_on_moe():
    """MoE expert FFNs bypass the LoRA proj() hook; asking for gate/up/
    down adapters on a MoE model must fail loudly, not silently no-op."""
    from production_stack_tpu.models import lora

    lcfg = lora.LoRAConfig(targets=("q", "gate"))
    with pytest.raises(ValueError, match="MoE"):
        lora.init_adapter(MOE_CFG, lcfg, jax.random.PRNGKey(0))
    # attention targets stay fine
    ad = lora.init_adapter(MOE_CFG, lora.LoRAConfig(targets=("q", "v")),
                           jax.random.PRNGKey(0))
    assert set(ad) == {"q", "v"}


def test_moe_capacity_factor_plumbs_to_model():
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine

    eng = LLMEngine(EngineConfig(model="debug-moe", max_model_len=64,
                                 moe_capacity_factor=3.5))
    assert eng.model_cfg.moe_capacity_factor == 3.5


def test_encode_moe_ignores_padding_content():
    """encode() (the embeddings path) masks padding: with right-padded
    batches, changing the pad tokens' content must not change any valid
    position's hidden state — pads neither route nor steal capacity.
    Uses a low capacity factor so the droppy dispatch branch is live."""
    cfg = ModelConfig(name="t-moe8", vocab_size=128, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=8,
                      num_kv_heads=4, max_position_embeddings=256,
                      num_experts=8, num_experts_per_tok=2,
                      moe_capacity_factor=0.8, dtype=jnp.float32)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    T = 120
    lengths = np.array([T, 40])
    toks = rng.integers(0, cfg.vocab_size, (2, T))
    mask = np.arange(T)[None, :] < lengths[:, None]

    toks_a = toks.copy()
    toks_b = toks.copy()
    toks_b[~mask] = 7    # different garbage in the pad region

    h_a = np.asarray(llama.encode(params, cfg, jnp.asarray(toks_a),
                                  token_valid=jnp.asarray(mask)))
    h_b = np.asarray(llama.encode(params, cfg, jnp.asarray(toks_b),
                                  token_valid=jnp.asarray(mask)))
    np.testing.assert_array_equal(h_a[mask], h_b[mask])
