"""Test configuration: force an 8-device virtual CPU mesh.

All tests run hardware-free: JAX is pinned to the CPU platform with 8
virtual devices so sharding/collective code paths (tp/dp/sp meshes) are
exercised exactly as they would be on an 8-chip TPU slice.

Must run before jax is imported anywhere in the test process.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may have a TPU-tunnel PJRT plugin ("axon") registered via
# sitecustomize; its backend init dials a local relay and can block every
# jax.devices() call (even CPU-pinned) if the tunnel is down. Tests must be
# hardware-free, so drop the plugin's backend factory before any backend
# initialization happens.
try:
    import jax

    # sitecustomize may have imported jax already with JAX_PLATFORMS=axon
    # baked in; override the live config, not just the env var.
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge

    for _reg in ("_backend_factories", "backend_factories"):
        _factories = getattr(xla_bridge, _reg, None)
        if _factories is not None and "axon" in _factories:
            _factories.pop("axon")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
