"""Router resilience layer: breaker transitions, pre-stream failover,
retry budget, ring stability across health flaps, drain semantics, and
the chaos rig's fake-engine smoke (the real-engine chaos run is behind
the ``slow`` marker).

Unit tier drives HealthTracker/RetryBudget with an injected clock; the
e2e tier runs the real router app in-process against fault-injecting
FakeEngines (tests/fake_engine.py fault modes).
"""

import asyncio
import collections

import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router.app import build_app, parse_args
from production_stack_tpu.router.resilience import (CLOSED, HALF_OPEN,
                                                    OPEN, HealthTracker,
                                                    RetryBudget,
                                                    backoff_s,
                                                    wait_for_drain)
from production_stack_tpu.router.routing import (LeastLoadedRouter,
                                                 SessionRouter)
from production_stack_tpu.router.service_discovery import (
    EndpointInfo, StaticServiceDiscovery)
from production_stack_tpu.router.stats import RequestStats
from tests.fake_engine import FakeEngine

URL = "http://e0:8100"


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ------------------------------------------------------------- unit tier

def test_breaker_opens_on_consecutive_failures_and_reprobes():
    clock = Clock()
    t = HealthTracker(failure_threshold=3, cooldown_s=5.0, now_fn=clock)
    assert t.is_routable(URL)
    t.record_failure(URL, "connect")
    t.record_failure(URL, "connect")
    assert t.is_routable(URL)          # under threshold
    t.record_failure(URL, "timeout")
    assert t.state_of(URL) == OPEN
    assert not t.is_routable(URL)
    assert t.breaker_opens == 1

    # a success mid-open (fail-open fallback traffic) closes it
    t.record_success(URL)
    assert t.state_of(URL) == CLOSED and t.is_routable(URL)

    # re-open, then the active-probe path: fail -> re-open; ok -> close
    for _ in range(3):
        t.record_failure(URL, "connect")
    assert t.state_of(URL) == OPEN
    t.record_probe_result(URL, False)
    assert t.state_of(URL) == OPEN     # probe failure re-opens/extends
    t.record_probe_result(URL, True)
    assert t.state_of(URL) == CLOSED
    assert t.recoveries >= 1


def test_breaker_failure_rate_trip():
    clock = Clock()
    t = HealthTracker(failure_threshold=100, failure_rate=0.5,
                      min_samples=20, window_s=30.0, now_fn=clock)
    # alternate ok/fail: consecutive never reaches 100, but the rate
    # hits 50% once min_samples accumulate (the very first success is
    # a no-op — endpoints start healthy with no tracked state — so 11
    # rounds yield 21 samples, 11 of them failures)
    for _ in range(11):
        t.record_success(URL)
        t.record_failure(URL, "http_5xx")
    assert t.state_of(URL) == OPEN


def test_breaker_half_open_requires_probe():
    clock = Clock()
    t = HealthTracker(failure_threshold=1, cooldown_s=5.0, now_fn=clock)
    t.record_failure(URL, "connect")
    assert t.state_of(URL) == OPEN
    clock.t = 10.0                     # cooldown long past
    # probe pass flips to HALF_OPEN then records the (failed) probe;
    # with no server, probe_model_name returns None -> re-open
    async def probe():
        await t.probe_open_endpoints(_DummyProbeSession(None))
    asyncio.run(probe())
    assert t.state_of(URL) == OPEN     # failed probe: open again
    assert not t.is_routable(URL)


class _DummyProbeSession:
    """Stands in for aiohttp.ClientSession: .get raises (unreachable)
    or returns a canned /v1/models response."""

    def __init__(self, models):
        self._models = models

    def get(self, url, **kw):
        models = self._models

        class _Ctx:
            async def __aenter__(self):
                if models is None:
                    import aiohttp
                    raise aiohttp.ClientError("probe refused")

                class _R:
                    status = 200

                    async def json(self):
                        return {"data": [{"id": m} for m in models]}
                return _R()

            async def __aexit__(self, *exc):
                return False
        return _Ctx()


def test_breaker_probe_success_closes():
    clock = Clock()
    t = HealthTracker(failure_threshold=1, cooldown_s=2.0, now_fn=clock)
    t.record_failure(URL, "connect")
    clock.t = 3.0
    asyncio.run(t.probe_open_endpoints(_DummyProbeSession(["m"])))
    assert t.state_of(URL) == CLOSED
    assert t.is_routable(URL)


def test_retry_budget_bounds_retry_storms():
    b = RetryBudget(ratio=0.5, cap=2.0)
    assert b.try_spend() and b.try_spend()   # burst allowance
    assert not b.try_spend()                 # bucket empty
    assert b.rejected == 1
    b.on_request()                           # +0.5
    assert not b.try_spend()
    b.on_request()                           # +0.5 -> 1.0
    assert b.try_spend()
    # sustained: retries <= ratio * requests
    b2 = RetryBudget(ratio=0.2, cap=1.0)
    granted = 0
    for _ in range(100):
        b2.on_request()
        if b2.try_spend():
            granted += 1
    assert granted <= 0.2 * 100 + 1.0 + 1


def test_backoff_jitter_bounds():
    import random
    rng = random.Random(7)
    for attempt in range(1, 6):
        for _ in range(20):
            s = backoff_s(attempt, base_s=0.05, cap_s=1.0, rng=rng)
            assert 0.0 <= s <= min(1.0, 0.05 * 2 ** (attempt - 1))


def test_healthy_endpoints_filter_and_fail_open():
    t = HealthTracker(failure_threshold=1)
    eps = [EndpointInfo(url=f"http://e{i}:8100", model="m")
           for i in range(3)]
    assert t.healthy_endpoints(eps) == eps
    t.record_failure(eps[0].url, "connect")
    assert [e.url for e in t.healthy_endpoints(eps)] == \
        [eps[1].url, eps[2].url]
    # all unroutable -> fail open to non-draining, then to everything
    t.record_failure(eps[1].url, "connect")
    t.record_failure(eps[2].url, "connect")
    assert t.healthy_endpoints(eps) == eps
    t.start_drain(eps[0].url)
    assert [e.url for e in t.healthy_endpoints(eps)] == \
        [eps[1].url, eps[2].url]


def test_drain_state_machine():
    t = HealthTracker()
    t.start_drain(URL)
    assert not t.is_routable(URL)
    assert t.draining() == [URL]
    assert t.snapshot()[URL]["draining"]
    t.end_drain(URL)
    assert t.is_routable(URL)
    assert t.draining() == []


def test_session_ring_stable_across_health_flaps():
    """Health transitions remap ONLY the failed endpoint's sessions —
    deterministically — and return them on recovery."""
    router = SessionRouter()
    eps = [EndpointInfo(url=f"http://e{i}:8100", model="m")
           for i in range(4)]
    users = [f"user{i}" for i in range(200)]

    def mapping(pool):
        return {u: router.route(pool, {}, {"x-user-id": u}, {})
                for u in users}

    before = mapping(eps)
    dead = eps[1].url
    survivors = [e for e in eps if e.url != dead]
    during = mapping(survivors)
    moved = [u for u in users if before[u] != during[u]]
    # only the dead endpoint's sessions moved, each re-routed
    # deterministically (same answer every time)
    assert set(moved) == {u for u in users if before[u] == dead}
    assert during == mapping(survivors)
    # recovery: everyone returns to exactly the original endpoint
    assert mapping(eps) == before


def test_least_loaded_slow_start_ramp():
    clock = Clock()
    r = LeastLoadedRouter(slow_start_s=10.0, now_fn=clock)
    e0 = EndpointInfo(url="http://e0:8100", model="m")
    e1 = EndpointInfo(url="http://e1:8100", model="m")
    stats = {"http://e0:8100": RequestStats(in_flight=6, qps=3.0)}
    # warm the router on e0 alone (cold start ramps nothing)
    r.route([e0], stats, {}, {})
    # t=1: e1 joins the fleet — it carries a virtual load just above
    # the busiest known endpoint, so it does NOT absorb the arrival
    # burst the moment it appears
    clock.t = 1.0
    picks = collections.Counter(
        r.route([e0, e1], stats, {}, {}) for _ in range(10))
    assert picks["http://e1:8100"] == 0
    # halfway through the ramp the virtual load decays below e0's real
    # load and e1 starts winning
    clock.t = 7.0
    assert r.route([e0, e1], stats, {}, {}) == "http://e1:8100"
    # slow start disabled -> old behavior (idle endpoint wins at once)
    r0 = LeastLoadedRouter(slow_start_s=0.0, now_fn=clock)
    assert r0.route([e0, e1], stats, {}, {}) == "http://e1:8100"


def test_least_loaded_slow_start_after_breaker_recovery():
    """An endpoint returning after a health-filtered absence ramps even
    though it is still present in the stats snapshot (in_flight 0)."""
    clock = Clock()
    r = LeastLoadedRouter(slow_start_s=10.0, absent_reset_s=2.0,
                          now_fn=clock)
    e0 = EndpointInfo(url="http://e0:8100", model="m")
    e1 = EndpointInfo(url="http://e1:8100", model="m")
    stats = {"http://e0:8100": RequestStats(in_flight=4, qps=2.0),
             "http://e1:8100": RequestStats(in_flight=0, qps=1.0)}
    r.route([e0, e1], stats, {}, {})           # both known (no ramp)
    # e1's breaker opens: 5s of routing happens without it
    for t in (1.0, 2.0, 3.0, 4.0, 5.0):
        clock.t = t
        r.route([e0], stats, {}, {})
    # e1 recovers at t=5: despite its idle snapshot entry it must NOT
    # swallow the whole burst — the ramp restarts
    picks = collections.Counter(
        r.route([e0, e1], stats, {}, {}) for _ in range(10))
    assert picks["http://e1:8100"] == 0
    clock.t = 12.0                             # ramp decayed below e0
    assert r.route([e0, e1], stats, {}, {}) == "http://e1:8100"
    # an IDLE router (no calls at all for a while) resets nobody
    clock.t = 30.0
    assert r.route([e0, e1], stats, {}, {}) == "http://e1:8100"


# -------------------------------------------------------------- e2e tier

def _router_args(backends, models, extra=None):
    argv = ["--service-discovery", "static",
            "--static-backends", ",".join(backends),
            "--static-models", ",".join(models),
            "--engine-stats-interval", "0.2",
            "--breaker-threshold", "2",
            "--breaker-cooldown", "0.3",
            "--breaker-probe-interval", "0.15"]
    return parse_args(argv + (extra or []))


async def _start_fakes(*fakes):
    servers = []
    for fake in fakes:
        server = TestServer(fake.build_app())
        await server.start_server()
        servers.append(server)
    return servers, [f"http://127.0.0.1:{s.port}" for s in servers]


def _chat(model="m", stream=False):
    return {"model": model, "stream": stream,
            "messages": [{"role": "user", "content": "hi"}]}


def test_failover_masks_dead_backend():
    """A backend resetting every connection is failed over pre-stream:
    clients always see 200, the breaker opens, and /metrics says so."""
    async def body():
        good, bad = FakeEngine(model="m"), FakeEngine(model="m")
        bad.fault = {"mode": "reset", "count": -1, "scope": "inference"}
        servers, urls = await _start_fakes(good, bad)
        app = build_app(_router_args(urls, ["m", "m"]))
        async with TestClient(TestServer(app)) as client:
            for _ in range(8):
                r = await client.post("/v1/chat/completions",
                                      json=_chat())
                assert r.status == 200, await r.text()
            assert len(good.requests_seen) == 8
            tracker = app["state"]["health"]
            assert tracker.state_of(urls[1]) in (OPEN, HALF_OPEN)
            assert tracker.retries[urls[1]] >= 1

            r = await client.get("/metrics")
            text = (await r.read()).decode()
            assert "vllm:upstream_failures_total" in text
            assert "vllm:healthy_pods_total 1.0" in text
            assert 'vllm:breaker_state{server="%s"}' % urls[1] in text

            r = await client.get("/health")
            h = await r.json()
            assert h["healthy_endpoints"] == 1
            assert h["breakers"][urls[1]]["state"] in ("open",
                                                       "half_open")
        for s in servers:
            await s.close()
    asyncio.run(body())


def test_backend_5xx_failover_and_accounting():
    """Backend 500s before any byte reached the client are retried on
    another endpoint; the 5xx is counted per endpoint, not relayed."""
    async def body():
        good, sick = FakeEngine(model="m"), FakeEngine(model="m")
        sick.fault = {"mode": "error", "count": -1, "scope": "inference"}
        servers, urls = await _start_fakes(good, sick)
        app = build_app(_router_args(urls, ["m", "m"]))
        async with TestClient(TestServer(app)) as client:
            for _ in range(6):
                r = await client.post("/v1/chat/completions",
                                      json=_chat())
                assert r.status == 200, await r.text()
            tracker = app["state"]["health"]
            assert tracker.failures[(urls[1], "http_5xx")] >= 1
            assert tracker.relayed_5xx.get(urls[1], 0) == 0
        for s in servers:
            await s.close()
    asyncio.run(body())


def test_single_backend_failure_is_terminal():
    """With no alternative candidate there is nothing to fail over to:
    the client still gets the structured 502 (and quickly)."""
    async def body():
        app = build_app(_router_args(["http://127.0.0.1:1"], ["m"]))
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/chat/completions", json=_chat())
            assert r.status == 502
            err = await r.json()
            assert err["error"]["type"] == "server_error"
    asyncio.run(body())


def test_sticky_session_fails_over_and_returns():
    """Acceptance pin: a sticky session re-routes off its dead endpoint
    within one breaker-open interval and RETURNS to it on recovery."""
    async def body():
        f = [FakeEngine(model="m") for _ in range(2)]
        servers, urls = await _start_fakes(*f)
        app = build_app(_router_args(urls, ["m", "m"],
                                     ["--routing-logic", "session"]))
        async with TestClient(TestServer(app)) as client:
            hdr = {"x-user-id": "alice"}
            for _ in range(3):
                r = await client.post("/v1/chat/completions",
                                      json=_chat(), headers=hdr)
                assert r.status == 200
            home = 0 if len(f[0].requests_seen) == 3 else 1
            away = 1 - home
            assert len(f[home].requests_seen) == 3

            # home engine dies (probes fail too: a fully dead pod)
            f[home].fault = {"mode": "reset", "count": -1,
                             "scope": "all"}
            for _ in range(4):
                r = await client.post("/v1/chat/completions",
                                      json=_chat(), headers=hdr)
                assert r.status == 200     # failover, not 502
            assert len(f[away].requests_seen) == 4

            # recovery: clear the fault, wait for the active re-probe
            # (cooldown 0.3s + probe every 0.15s) to close the breaker
            f[home].fault = None
            tracker = app["state"]["health"]
            for _ in range(40):
                if tracker.state_of(urls[home]) == CLOSED:
                    break
                await asyncio.sleep(0.1)
            assert tracker.state_of(urls[home]) == CLOSED

            before = len(f[home].requests_seen)
            for _ in range(3):
                r = await client.post("/v1/chat/completions",
                                      json=_chat(), headers=hdr)
                assert r.status == 200
            # the session went home (deterministic ring restoration)
            assert len(f[home].requests_seen) == before + 3
        for s in servers:
            await s.close()
    asyncio.run(body())


def test_client_abort_is_not_a_backend_failure():
    """Clients hitting stop mid-stream must not feed the breaker: a
    few aborts against one endpoint would otherwise pull a healthy
    engine out of rotation (breaker threshold is 2 here)."""
    async def body():
        fake = FakeEngine(model="m", num_tokens=200, tokens_per_s=50.0)
        servers, urls = await _start_fakes(fake)
        app = build_app(_router_args(urls, ["m"]))
        async with TestClient(TestServer(app)) as client:
            for _ in range(4):
                resp = await client.post("/v1/chat/completions",
                                         json=_chat(stream=True))
                assert resp.status == 200
                await resp.content.read(10)   # stream is live...
                resp.close()                  # ...client walks away
            await asyncio.sleep(0.3)          # let relays notice
            tracker = app["state"]["health"]
            assert tracker.state_of(urls[0]) == CLOSED
            assert tracker.failures.get((urls[0], "mid_stream"), 0) == 0
            # and the endpoint still serves new requests
            r = await client.post("/v1/chat/completions",
                                  json=_chat())
            assert r.status == 200
        for s in servers:
            await s.close()
    asyncio.run(body())


def test_admin_drain_endpoint():
    """POST /admin/drain stops new admissions to an engine; ending the
    drain readmits it."""
    async def body():
        f1, f2 = FakeEngine(model="m"), FakeEngine(model="m")
        servers, urls = await _start_fakes(f1, f2)
        app = build_app(_router_args(urls, ["m", "m"]))
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/admin/drain",
                                  json={"url": urls[0]})
            assert r.status == 200
            assert (await r.json())["draining"] == [urls[0]]
            for _ in range(4):
                r = await client.post("/v1/chat/completions",
                                      json=_chat())
                assert r.status == 200
            assert len(f1.requests_seen) == 0
            assert len(f2.requests_seen) == 4

            r = await client.post("/admin/drain",
                                  json={"url": urls[0],
                                        "drain": False})
            assert (await r.json())["draining"] == []
            for _ in range(4):
                await client.post("/v1/chat/completions", json=_chat())
            assert len(f1.requests_seen) > 0   # readmitted (roundrobin)

            r = await client.post("/admin/drain", json={"nope": 1})
            assert r.status == 400
            # a typo'd endpoint must not become a silent no-op drain
            r = await client.post("/admin/drain",
                                  json={"url": "http://typo:1234"})
            assert r.status == 404
        for s in servers:
            await s.close()
    asyncio.run(body())


def test_inflight_tracking_and_drain_wait():
    """The app counts in-flight handlers; wait_for_drain resolves once
    the last one finishes (the SIGTERM path's building blocks)."""
    async def body():
        fake = FakeEngine(model="m", num_tokens=6, tokens_per_s=20.0)
        servers, urls = await _start_fakes(fake)
        app = build_app(_router_args(urls, ["m"]))
        async with TestClient(TestServer(app)) as client:
            state = app["state"]
            assert state["inflight"] == 0
            task = asyncio.create_task(
                client.post("/v1/chat/completions",
                            json=_chat(stream=True)))
            await asyncio.sleep(0.1)
            assert state["inflight"] >= 1
            drained = await wait_for_drain(lambda: state["inflight"],
                                           timeout_s=10.0)
            assert drained and state["inflight"] == 0
            r = await task
            assert r.status == 200
        for s in servers:
            await s.close()
    asyncio.run(body())


def test_static_discovery_marks_probe_dead_unroutable():
    """K consecutive /v1/models probe failures drop the endpoint from
    discovery; a later successful probe readmits it (satellite)."""
    async def body():
        f1, f2 = FakeEngine(model="m"), FakeEngine(model="m")
        servers, urls = await _start_fakes(f1, f2)
        tracker = HealthTracker()
        disco = StaticServiceDiscovery(
            urls, ["m", "m"], probe=True, probe_interval=0.05,
            probe_failure_threshold=2, health_tracker=tracker)
        await disco.start()
        try:
            assert len(disco.get_endpoints()) == 2
            f2.fault = {"mode": "error", "count": -1, "scope": "all"}
            for _ in range(60):
                if len(disco.get_endpoints()) == 1:
                    break
                await asyncio.sleep(0.05)
            assert [ep.url for ep in disco.get_endpoints()] == [urls[0]]

            f2.fault = None
            for _ in range(60):
                if len(disco.get_endpoints()) == 2:
                    break
                await asyncio.sleep(0.05)
            assert len(disco.get_endpoints()) == 2
            # probe outcomes fed the shared health state
            assert tracker.failures[(urls[1], "probe")] >= 2
        finally:
            await disco.close()
        for s in servers:
            await s.close()
    asyncio.run(body())


def test_fake_engine_fault_control_endpoint():
    """The /fault control surface: set, observe, burst-decrement,
    clear."""
    async def body():
        fake = FakeEngine(model="m")
        servers, urls = await _start_fakes(fake)
        async with TestClient(TestServer(fake.build_app())) as client:
            r = await client.post("/fault", json={"mode": "error",
                                                  "count": 2})
            assert r.status == 200
            r = await client.post("/v1/chat/completions", json=_chat())
            assert r.status == 500
            r = await client.post("/v1/chat/completions", json=_chat())
            assert r.status == 500
            r = await client.post("/v1/chat/completions", json=_chat())
            assert r.status == 200        # burst exhausted
            r = await client.get("/fault")
            assert (await r.json())["faults_served"] == 2

            r = await client.post("/fault", json={"mode": "bogus"})
            assert r.status == 400
            r = await client.post("/fault", json={"mode": None})
            assert (await r.json())["fault"] is None
        for s in servers:
            await s.close()
    asyncio.run(body())


# ------------------------------------------------------------ chaos tier

def _assert_chaos_clean(record):
    from production_stack_tpu.loadgen.chaos import chaos_violations
    d = record["detail"]
    assert record["unit"] == "%"
    assert d["requests"]["launched"] > 0
    assert d["kills"] >= 1 and d["restarts"] >= 1
    violations = chaos_violations(record)
    assert not violations, violations


def test_chaos_smoke_fake_engines(tmp_path):
    """Tier-1 chaos smoke: real router + 2 fake engine processes, one
    scheduled kill/restart inside a short storm — zero client-visible
    5xx, zero router transport errors."""
    from production_stack_tpu.loadgen.chaos import run_chaos
    record = asyncio.run(run_chaos(
        engines=2, users=4, duration_s=10.0, kill_interval_s=3.0,
        downtime_s=1.5, error_burst_interval_s=4.0, error_burst=3,
        stream_fraction=0.3, num_tokens=4, seed=1,
        log_dir=str(tmp_path / "logs")))
    _assert_chaos_clean(record)


@pytest.mark.slow
def test_chaos_real_engine(tmp_path):
    """The same churn against real debug-tiny engines on CPU."""
    from production_stack_tpu.loadgen.chaos import run_chaos
    record = asyncio.run(run_chaos(
        engines=2, engine="debug-tiny", users=4, duration_s=45.0,
        kill_interval_s=15.0, downtime_s=5.0,
        error_burst_interval_s=None, num_tokens=8, seed=1,
        log_dir=str(tmp_path / "logs")))
    _assert_chaos_clean(record)


# ------------------------------------- dynamic-config vs failover race

def test_config_swap_mid_failover_does_not_resurrect_removed_endpoint():
    """A dynamic-config apply that removes an endpoint while another
    endpoint is mid-retry must not see the removed one resurrected
    from the in-flight failover loop's captured candidate list.

    Shape: session s homes on W (stalling); the consistent-hash
    successor once W is excluded is X. Mid-stall, a config apply
    removes X from the fleet. When W times out, the failover re-route
    must land on Y — the only endpoint that is both untried and still
    CONFIGURED — and X must never receive an inference request."""
    from production_stack_tpu.router.dynamic_config import (
        DynamicConfigWatcher, DynamicRouterConfig)
    from production_stack_tpu.router.routing import HashRing

    async def body():
        w = FakeEngine(model="m")
        w.fault = {"mode": "stall", "count": -1, "scope": "inference"}
        x = FakeEngine(model="m")
        x.fault = {"mode": "reset", "count": -1, "scope": "inference"}
        y = FakeEngine(model="m")
        servers, urls = await _start_fakes(w, x, y)
        w_url, x_url, y_url = urls

        # find a session id that homes on W in the full ring and on X
        # once W is excluded (the resurrection target)
        full, sub = HashRing(), HashRing()
        full.rebuild(urls)
        sub.rebuild([x_url, y_url])
        session = next(
            s for s in (f"race-sess-{i}" for i in range(4096))
            if full.lookup(s) == w_url and sub.lookup(s) == x_url)

        app = build_app(_router_args(
            urls, ["m", "m", "m"],
            extra=["--routing-logic", "session",
                   "--request-timeout", "1",
                   "--failover-attempts", "3",
                   "--breaker-threshold", "10"]))
        watcher = DynamicConfigWatcher(app["state"], path="unused")
        cfg = DynamicRouterConfig(
            service_discovery="static", routing_logic="session",
            static_backends=[w_url, y_url], static_models=["m", "m"])
        async with TestClient(TestServer(app)) as client:
            req = asyncio.ensure_future(client.post(
                "/v1/chat/completions", json=_chat(),
                headers={"x-user-id": session}))
            await asyncio.sleep(0.4)      # W is mid-stall being retried
            await watcher._apply(cfg)     # removes X from the fleet
            resp = await req
            assert resp.status == 200, await resp.text()
            # Y (configured, untried) served it
            assert y.last_headers, "Y never saw the failover re-route"
            # the removed endpoint was NOT resurrected mid-failover
            assert not x.last_headers, (
                "X received an inference request AFTER the config "
                "apply removed it from the fleet")
        for s in servers:
            await s.close()
    asyncio.run(body())
