"""GPipe pipeline-parallel training (parallel/pipeline.py) on the
8-device virtual CPU mesh: loss and gradient parity vs the plain
(unpipelined) loss, and stage-split validation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from production_stack_tpu.models import ModelConfig, llama
from production_stack_tpu.parallel.mesh import MeshConfig, build_mesh
from production_stack_tpu.parallel.pipeline import (pipeline_loss_fn,
                                                    stage_params,
                                                    stage_shardings)
from production_stack_tpu.parallel.train import loss_fn as plain_loss_fn

CFG = ModelConfig(name="t-pp", vocab_size=128, hidden_size=64,
                  intermediate_size=128, num_layers=4, num_heads=4,
                  num_kv_heads=2, max_position_embeddings=128,
                  dtype=jnp.float32)


@pytest.fixture(scope="module")
def pp_setup():
    mesh = build_mesh(MeshConfig(pp=4), jax.devices()[:4])
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    staged = stage_params(params, 4)
    staged = jax.device_put(staged, stage_shardings(mesh, staged))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                CFG.vocab_size)
    return mesh, params, staged, tokens


def test_pipeline_loss_matches_plain(pp_setup):
    mesh, params, staged, tokens = pp_setup
    plain = float(plain_loss_fn(params, CFG, tokens))
    piped = float(jax.jit(pipeline_loss_fn(CFG, mesh, n_micro=4))(
        staged, tokens))
    assert abs(plain - piped) < 1e-4, (plain, piped)


def test_pipeline_grads_match_plain(pp_setup):
    """The backward pass through the ppermute schedule is the reverse
    pipeline; layer gradients must equal the unpipelined ones."""
    mesh, params, staged, tokens = pp_setup
    g_plain = jax.grad(lambda p: plain_loss_fn(p, CFG, tokens))(params)
    g_piped = jax.grad(jax.jit(pipeline_loss_fn(CFG, mesh, n_micro=4)))(
        staged, tokens)
    for name, g in g_plain["layers"].items():
        got = np.asarray(g_piped["layers"][name]).reshape(np.asarray(g).shape)
        np.testing.assert_allclose(got, np.asarray(g), atol=2e-4,
                                   rtol=2e-3, err_msg=name)
    np.testing.assert_allclose(np.asarray(g_piped["embed"]),
                               np.asarray(g_plain["embed"]),
                               atol=2e-4, rtol=2e-3)


def test_pipeline_single_microbatch_still_correct(pp_setup):
    """n_micro=1 (pure bubble) must still compute the same loss."""
    mesh, params, staged, tokens = pp_setup
    plain = float(plain_loss_fn(params, CFG, tokens))
    piped = float(jax.jit(pipeline_loss_fn(CFG, mesh, n_micro=1))(
        staged, tokens))
    assert abs(plain - piped) < 1e-4


def test_stage_split_validation():
    params = llama.init_params(CFG, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="divide"):
        stage_params(params, 3)
    staged = stage_params(params, 2)
    assert jax.tree.leaves(staged["layers"])[0].shape[0] == 2
