"""Full-stack test: router -> real TPU-native engine (CPU, debug-tiny).

The reference never tests its router against a real engine outside a
cluster; here the whole stack runs in-process: real engine server behind
the real router, streaming included.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.engine.async_engine import AsyncLLMEngine
from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.server import build_app as build_engine_app
from production_stack_tpu.router.app import build_app as build_router_app
from production_stack_tpu.router.app import parse_args


@pytest.fixture(scope="module")
def engine():
    cfg = EngineConfig(model="debug-tiny", max_model_len=128, max_num_seqs=2,
                       prefill_chunk=32, prefill_buckets=(16, 32))
    eng = AsyncLLMEngine(cfg)
    eng.engine.runner.warmup()
    return eng


def test_router_to_real_engine(engine):
    async def body():
        engine_server = TestServer(build_engine_app(engine))
        await engine_server.start_server()
        url = f"http://127.0.0.1:{engine_server.port}"
        router_app = build_router_app(parse_args([
            "--service-discovery", "static",
            "--static-backends", url,
            "--static-models", "debug-tiny"]))
        async with TestClient(TestServer(router_app)) as client:
            r = await client.get("/v1/models")
            assert [c["id"] for c in (await r.json())["data"]] == [
                "debug-tiny"]

            r = await client.post("/v1/chat/completions", json={
                "model": "debug-tiny", "max_tokens": 5, "temperature": 0.0,
                "messages": [{"role": "user", "content": "hello"}]})
            assert r.status == 200
            data = await r.json()
            assert data["usage"]["completion_tokens"] == 5

            r = await client.post("/v1/chat/completions", json={
                "model": "debug-tiny", "max_tokens": 5, "stream": True,
                "messages": [{"role": "user", "content": "hello"}]})
            raw = (await r.read()).decode()
            assert raw.strip().endswith("data: [DONE]")

            r = await client.get("/health")
            assert (await r.json())["status"] == "ok"
        await engine_server.close()
    asyncio.run(body())


def test_router_to_secured_engine(engine, monkeypatch):
    """Secured serving e2e (VERDICT r3 missing #1): the engine enforces
    ENGINE_API_KEY; the router (holding the same key, as the chart
    delivers it) probes and proxies successfully, while a direct
    unauthenticated hit on the engine gets 401."""
    monkeypatch.setenv("ENGINE_API_KEY", "stack-key")

    async def body():
        engine_server = TestServer(
            build_engine_app(engine, api_key="stack-key"))
        await engine_server.start_server()
        url = f"http://127.0.0.1:{engine_server.port}"

        # direct, unauthenticated -> 401
        import aiohttp
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{url}/v1/models") as r:
                assert r.status == 401

        router_app = build_router_app(parse_args([
            "--service-discovery", "static",
            "--static-backends", url,
            "--static-models", "debug-tiny",
            "--probe-backends"]))
        async with TestClient(TestServer(router_app)) as client:
            # through the router, no client credentials: the router
            # injects its own Bearer (proxy._forward_headers)
            r = await client.post("/v1/chat/completions", json={
                "model": "debug-tiny", "max_tokens": 3,
                "temperature": 0.0,
                "messages": [{"role": "user", "content": "hello"}]})
            assert r.status == 200
            assert (await r.json())["usage"]["completion_tokens"] == 3

            # a client-provided WRONG Bearer passes through untouched
            # and is rejected by the engine — per-client keys are the
            # engine's decision, not the router's
            r = await client.post(
                "/v1/chat/completions",
                headers={"Authorization": "Bearer wrong"},
                json={"model": "debug-tiny", "max_tokens": 3,
                      "messages": [{"role": "user", "content": "x"}]})
            assert r.status == 401
        await engine_server.close()
    asyncio.run(body())
