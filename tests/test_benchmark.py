"""Benchmark harness tests: workload cadence, client SSE parsing, and a
small end-to-end run against the fake engine (reference pattern: the
perftest tier drives the real tooling against mocks, SURVEY.md §4.2)."""

import asyncio

from aiohttp.test_utils import TestServer

from benchmarks.multi_round_qa.client import StreamingClient
from benchmarks.multi_round_qa.summary import summarize, write_csv
from benchmarks.multi_round_qa.workload import (SessionManager, UserSession,
                                                WorkloadConfig)
from tests.fake_engine import FakeEngine


def test_workload_cadence_math():
    cfg = WorkloadConfig(num_users=10, num_rounds=5, qps=2.0)
    assert cfg.gap_between_requests == 5.0        # 10 users / 2 qps
    assert cfg.session_lifetime == 20.0           # 4 gaps
    assert cfg.gap_between_users == 2.0           # stationary population


def test_fast_forward_places_session_mid_life():
    cfg = WorkloadConfig(num_users=4, num_rounds=10, qps=1.0)
    s = UserSession(1, cfg)
    now = 1000.0
    s.fast_forward(offset=9.0, now=now)           # gap=4s -> 3 questions in
    assert s.question_id == 3
    # next request becomes due one gap after the (virtual) last one
    assert s.last_request_time == now - 9.0 + 2 * cfg.gap_between_requests


def test_ramp_up_creates_full_population():
    cfg = WorkloadConfig(num_users=5, num_rounds=4, qps=5.0)
    mgr = SessionManager(cfg)
    mgr._ramp_up(now=0.0)
    assert len(mgr.sessions) == cfg.num_users
    # sessions are staggered across their lifetime, not all at question 1
    qids = {s.question_id for s in mgr.sessions}
    assert len(qids) > 1


def test_benchmark_end_to_end_against_fake_engine(tmp_path):
    async def body():
        fake = FakeEngine(model="bench-model", num_tokens=4)
        server = TestServer(fake.build_app())
        await server.start_server()
        url = f"http://127.0.0.1:{server.port}"

        cfg = WorkloadConfig(num_users=3, num_rounds=4, qps=30.0,
                             system_prompt_len=20, user_history_len=10,
                             answer_len=4)
        mgr = SessionManager(cfg, continuous=False)
        client = StreamingClient(url, "bench-model")
        await client.start()
        deadline = asyncio.get_event_loop().time() + 30
        import time
        while asyncio.get_event_loop().time() < deadline:
            mgr.step(time.time(), client)
            if not mgr.sessions and mgr.done_sessions:
                break
            await asyncio.sleep(0.02)
        while client.in_flight:
            await asyncio.sleep(0.02)
        results = mgr.all_results()
        await client.close()
        await server.close()

        # every finished session produced num_rounds results
        assert len(mgr.done_sessions) >= cfg.num_users
        assert all(r.error is None for r in results), results
        assert all(r.generation_tokens == 4 for r in results)
        assert all(r.ttft > 0 for r in results)
        # multi-round: assistant turns fed back into each history (ramp-up
        # fast-forwards sessions mid-life, so counts vary per session but
        # must always match that session's completed rounds)
        multi = [s for s in mgr.done_sessions if len(s.results) >= 2]
        assert multi, [len(s.results) for s in mgr.done_sessions]
        for s in multi:
            roles = [m["role"] for m in s.messages]
            assert roles.count("user") == len(s.results)
            assert roles.count("assistant") == len(s.results)
        # session affinity header flowed on every request
        users = {u for _, u, _ in fake.requests_seen}
        assert all(u is not None for u in users)

        s = summarize(results)
        assert s.finished_requests == len(results)
        assert s.output_tokens_per_s > 0
        assert s.mean_ttft > 0
        out = tmp_path / "bench.csv"
        write_csv(results, str(out))
        assert out.read_text().count("\n") == len(results) + 1
    asyncio.run(body())


def test_sharegpt_workload(tmp_path):
    """--sharegpt: questions come from the dump's human turns, cycled
    per user (reference multi-round-qa.py --sharegpt mode)."""
    import json

    from benchmarks.multi_round_qa.workload import (UserSession,
                                                    WorkloadConfig,
                                                    load_sharegpt)

    path = tmp_path / "sg.json"
    path.write_text(json.dumps([
        {"conversations": [
            {"from": "human", "value": "What is the capital of France?"},
            {"from": "gpt", "value": "Paris."},
            {"from": "human", "value": "And of Italy?"}]},
        {"conversations": [
            {"from": "user", "value": "Explain entropy."},
            {"from": "gpt", "value": "..."}]},
        {"conversations": [{"from": "gpt", "value": "orphan answer"}]},
    ]))
    convs = load_sharegpt(str(path))
    assert convs == [["What is the capital of France?", "And of Italy?"],
                     ["Explain entropy."]]

    cfg = WorkloadConfig(num_users=2, num_rounds=3, qps=1.0,
                         sharegpt=convs)
    u0 = UserSession(0, cfg)
    assert u0._next_question() == "What is the capital of France?"
    assert u0._next_question() == "And of Italy?"
    assert u0._next_question() == "What is the capital of France?"  # wraps
    u1 = UserSession(1, cfg)
    assert u1._next_question() == "Explain entropy."

    import pytest
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.json"
        bad.write_text("[]")
        load_sharegpt(str(bad))
