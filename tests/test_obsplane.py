"""Obsplane units + in-process e2e: online stitching, attribution,
the incident recorder, the fleet metrics surface, and the aggregator
polling a real fake-engine + scripted-router pair over HTTP.

The full subprocess fleet (routers + engines + obsplane + faults) is
exercised by tests/test_loadgen_incident.py; this file holds the
pieces that need no subprocess.
"""

import asyncio
import json
import os
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from production_stack_tpu.obsplane.aggregator import (FleetAggregator,
                                                      ProcessState)
from production_stack_tpu.obsplane.metrics import FleetMetrics
from production_stack_tpu.obsplane.recorder import (IncidentRecorder,
                                                    attribute_incident)
from production_stack_tpu.obsplane.stitch import ChainStore, percentile


# ------------------------------------------------------------ helpers

def _trace(tid, *, service="router", cls=None, dur=100.0, seq=1,
           spans=(), started_at=None, unattributed=0.0):
    return {
        "trace_id": tid, "span_id": "s" * 16, "parent_id": None,
        "seq": seq, "name": "/v1/chat/completions", "status": "ok",
        "started_at": started_at if started_at is not None
        else time.time(),
        "duration_ms": dur, "unattributed_ms": unattributed,
        "attrs": {"class": cls} if cls else {},
        "spans": [{"name": n, "kind": "phase", "start_ms": 0.0,
                   "duration_ms": d, "status": "ok"}
                  for n, d in spans],
    }


# ------------------------------------------------------------ stitch

def test_percentile_interpolates():
    assert percentile([], 50) == 0.0
    assert percentile([5.0], 99) == 5.0
    vals = [1.0, 2.0, 3.0, 4.0]
    assert percentile(vals, 0) == 1.0
    assert percentile(vals, 100) == 4.0
    assert percentile(vals, 50) == 2.5


def test_chainstore_joins_router_and_engine_sides():
    store = ChainStore()
    store.ingest("http://r", "router",
                 [_trace("a" * 32, cls="chat", dur=120.0,
                         spans=[("admission", 1.0),
                                ("backend_ttfb", 80.0)])])
    assert store.chains_complete == 0
    store.ingest("http://e1", "engine",
                 [_trace("a" * 32, service="engine",
                         spans=[("prefill", 70.0), ("decode", 30.0)])])
    assert store.chains_complete == 1
    assert store.stats()["complete_fraction"] == 1.0
    top = store.slowest(5)
    assert len(top) == 1
    chain = top[0]
    assert chain["class"] == "chat"
    assert chain["router"]["url"] == "http://r"
    assert chain["engines"]["http://e1"]["prefill"] == 70.0
    pct = store.fleet_percentiles()
    assert pct["chat"]["engine.prefill"]["n"] == 1
    assert pct["chat"]["router.backend_ttfb"]["p50_ms"] == 80.0
    assert pct["chat"]["total"]["p50_ms"] == 120.0


def test_chainstore_duplicate_rows_do_not_double_count():
    store = ChainStore()
    rows = [_trace("b" * 32, cls="chat", spans=[("admission", 1.0)])]
    store.ingest("http://r", "router", rows)
    store.ingest("http://r", "router", rows)      # re-scrape
    assert store.traces_ingested == 1
    engine_rows = [_trace("b" * 32, spans=[("decode", 5.0)])]
    store.ingest("http://e", "engine", engine_rows)
    store.ingest("http://e", "engine", engine_rows)
    assert store.chains_complete == 1
    assert store.fleet_percentiles()["chat"]["engine.decode"]["n"] == 1


def test_chainstore_eviction_is_bounded():
    store = ChainStore(max_chains=16)
    for i in range(64):
        store.ingest("http://r", "router", [_trace(f"{i:032x}")])
    assert store.stats()["chains_held"] <= 16
    assert store.chains_evicted == 48


def test_chainstore_prefill_side_and_class_filter():
    store = ChainStore()
    tid = "c" * 32
    store.ingest("http://r", "router", [_trace(tid, cls="rag",
                                               spans=[("prefill_dispatch",
                                                       9.0)])])
    store.ingest("http://p", "prefill",
                 [_trace(tid, spans=[("prefill", 44.0)])])
    store.ingest("http://e", "engine",
                 [_trace(tid, spans=[("decode", 3.0)])])
    top = store.slowest(5, cls="rag")
    assert top and top[0]["prefill"]["http://p"]["prefill"] == 44.0
    assert store.slowest(5, cls="chat") == []
    assert store.fleet_percentiles()["rag"]["prefill.prefill"]["n"] == 1


def test_chainstore_process_phase_stats_lookback():
    now = {"t": 1000.0}
    store = ChainStore(now_fn=lambda: now["t"])
    store.ingest("http://e", "engine",
                 [_trace("d" * 32, started_at=900.0,
                         spans=[("prefill", 10.0)]),
                  _trace("e" * 32, started_at=995.0, seq=2,
                         spans=[("prefill", 400.0)])])
    all_stats = store.process_phase_stats()
    assert all_stats["http://e"]["prefill"]["n"] == 2
    recent = store.process_phase_stats(lookback_s=50.0)
    assert recent["http://e"]["prefill"]["n"] == 1
    assert recent["http://e"]["prefill"]["p95_ms"] == 400.0


# ------------------------------------------------------------ attribution

def _procs(**over):
    base = {
        "http://r1": {"url": "http://r1", "role": "router",
                      "ever_seen": True, "unreachable_since": None},
        "http://e1": {"url": "http://e1", "role": "engine",
                      "ever_seen": True, "unreachable_since": None},
        "http://e2": {"url": "http://e2", "role": "engine",
                      "ever_seen": True, "unreachable_since": None},
    }
    for url, patch in over.items():
        base[url] = {**base[url], **patch}
    return base


def test_attribute_dead_process_wins():
    verdict = attribute_incident(
        alert={"name": "chat_availability_page", "slo_kind":
               "availability"},
        processes=_procs(**{"http://e1":
                            {"unreachable_since": 123.0}}),
        process_phase_stats={"http://e2": {"prefill":
                                           {"p50_ms": 1, "p95_ms": 999,
                                            "n": 5}}})
    assert verdict["process"] == "http://e1"
    assert verdict["phase"] == "down"
    assert verdict["confidence"] == "high"


def test_attribute_never_seen_process_is_not_a_corpse():
    # a process that never answered (misconfigured URL) must not eat
    # every attribution
    verdict = attribute_incident(
        alert=None,
        processes=_procs(**{"http://e1": {"ever_seen": False,
                                          "unreachable_since": 5.0}}),
        process_phase_stats={})
    assert verdict["process"] != "http://e1"


def test_attribute_shed_alert_names_biggest_shedding_router():
    verdict = attribute_incident(
        alert={"name": "shed_rate_page", "slo": "shed_rate",
               "slo_kind": "shed_rate"},
        processes=_procs(),
        process_phase_stats={},
        shed_deltas={"http://r1": 250.0})
    assert verdict["process"] == "http://r1"
    assert verdict["phase"] == "admission"


def test_attribute_phase_excess_names_slow_engine():
    stats = {
        "http://e1": {"prefill": {"p50_ms": 2, "p95_ms": 3, "n": 20},
                      "decode": {"p50_ms": 5, "p95_ms": 6, "n": 20}},
        "http://e2": {"prefill": {"p50_ms": 390, "p95_ms": 410,
                                  "n": 20},
                      "decode": {"p50_ms": 5, "p95_ms": 7, "n": 20}},
        # the router's backend-facing phases measure the engine and
        # must never indict the router
        "http://r1": {"backend_ttfb": {"p50_ms": 395, "p95_ms": 420,
                                       "n": 40}},
    }
    verdict = attribute_incident(
        alert={"name": "chat_ttft_page", "slo_kind": "latency"},
        processes=_procs(), process_phase_stats=stats)
    assert verdict["process"] == "http://e2"
    assert verdict["phase"] == "prefill"
    assert verdict["evidence"]["scoreboard"][0]["process"] == "http://e2"


def test_attribute_nothing_stands_out():
    verdict = attribute_incident(alert=None, processes=_procs(),
                                 process_phase_stats={})
    assert verdict["process"] is None
    assert verdict["confidence"] == "none"


# ------------------------------------------------------------ recorder

def test_recorder_capture_retention_and_cooldown(tmp_path):
    now = {"t": 1000.0}
    rec = IncidentRecorder(str(tmp_path), retention=2, cooldown_s=10.0,
                           now_fn=lambda: now["t"])
    attribution = {"process": "http://e1", "role": "engine",
                   "phase": "down", "confidence": "high",
                   "reason": "r", "evidence": {}}

    def cap(force=False):
        return rec.capture(trigger="alert:x", alert={"name": "x"},
                           fleet={"processes": {}},
                           attribution=attribution, force=force)

    first = cap()
    assert first is not None
    assert os.path.exists(first["path"])
    bundle = rec.load(first["incident_id"])
    assert bundle["schema"] == "tpu-incident-bundle/v1"
    assert bundle["attribution"]["process"] == "http://e1"
    # cooldown suppresses, force bypasses
    assert cap() is None
    assert rec.suppressed_total == 1
    assert cap(force=True) is not None
    # retention: a third bundle evicts the first file
    now["t"] += 60.0
    third = cap()
    assert third is not None
    assert len(rec.index()) == 2
    assert not os.path.exists(first["path"])
    assert rec.load(first["incident_id"]) is None


# ------------------------------------------------------------ metrics

def test_fleet_metrics_families_render():
    agg = FleetAggregator(routers=["http://r1"],
                          engines=["http://e1", "http://e2"],
                          scrape_headers={})
    metrics = FleetMetrics()
    metrics.refresh(agg)
    text = metrics.render().decode()
    for family in ("tpu:fleet_processes", "tpu:fleet_chains_stitched",
                   "tpu:fleet_traces_ingested",
                   "tpu:fleet_alerts_firing",
                   "tpu:fleet_scrape_errors"):
        assert family in text, family
    # 2 engines + 1 router, none scraped yet -> pending
    assert 'tpu:fleet_processes{role="engine",state="pending"} 2.0' \
        in text


# ------------------------------------------------------------ aggregator e2e

def _scripted_router(firing):
    """Minimal router lookalike: /health, /alerts, /debug/traces."""
    from production_stack_tpu.tracing import (TraceRecorder,
                                              debug_traces_handler)
    tracer = TraceRecorder("router")

    async def health(r):
        return web.json_response({"status": "ok",
                                  "sheds": {"admission":
                                            firing.get("sheds", 0)},
                                  "breakers": {}})

    async def alerts(r):
        name = "chat_ttft_page"
        rows = [{"name": name, "slo": "chat_ttft", "severity": "page",
                 "state": "firing" if firing.get("on") else "inactive",
                 "firing_since": firing.get("since")}]
        return web.json_response({
            "enabled": True,
            "slos": [{"name": "chat_ttft", "kind": "latency"}],
            "alerts": rows,
            "firing": [name] if firing.get("on") else []})

    app = web.Application()
    app.router.add_get("/health", health)
    app.router.add_get("/alerts", alerts)
    app.router.add_get("/debug/traces",
                       debug_traces_handler(lambda: tracer))
    return app, tracer


def test_aggregator_polls_stitches_and_captures(tmp_path):
    async def body():
        from tests.fake_engine import FakeEngine
        import aiohttp
        fake = FakeEngine(model="m", num_tokens=4)
        eng_srv = TestServer(fake.build_app())
        await eng_srv.start_server()
        eng_url = f"http://127.0.0.1:{eng_srv.port}"
        firing = {"on": False, "since": None}
        rapp, tracer = _scripted_router(firing)
        rtr_srv = TestServer(rapp)
        await rtr_srv.start_server()
        rtr_url = f"http://127.0.0.1:{rtr_srv.port}"

        # one request through the fake, parented on a router trace
        trace = tracer.begin(name="/v1/chat/completions")
        trace.attrs["class"] = "chat"
        t0 = time.monotonic()
        async with aiohttp.ClientSession() as session:
            async with session.post(
                    f"{eng_url}/v1/chat/completions",
                    json={"model": "m",
                          "messages": [{"role": "user",
                                        "content": "x"}]},
                    headers={"traceparent":
                             trace.child_traceparent()}) as resp:
                assert resp.status == 200
        trace.add_phase("backend_ttfb", t0, time.monotonic(),
                        attrs={"server": eng_url})
        tracer.finish(trace, "ok")

        rec = IncidentRecorder(str(tmp_path), cooldown_s=0.0)
        agg = FleetAggregator(routers=[rtr_url], engines=[eng_url],
                              poll_interval_s=30.0, recorder=rec)
        await agg.start(poll=False)   # session only; we drive polls
        try:
            await agg.poll_once()
            snap = agg.fleet_snapshot()
            assert snap["processes"][eng_url]["state"] == "live"
            assert snap["chains"]["chains_complete"] == 1
            pct = snap["fleet_percentiles"]
            assert "engine.prefill" in pct["chat"]
            # engine perf payload scraped (the bundle body)
            assert agg.processes[eng_url].perf is not None
            assert agg.processes[eng_url].load is not None

            # quiet -> burning edge: exactly one capture, steady
            # firing does not re-capture
            firing.update(on=True, since=123.0)
            await agg.poll_once()
            await agg.poll_once()
            assert rec.captured_total == 1
            bundle = rec.load(rec.index()[0]["incident_id"])
            assert bundle["alert"]["name"] == "chat_ttft_page"
            assert bundle["fleet"]["processes"][eng_url]["perf"] \
                is not None
            # quiet again, then a NEW burn -> second capture
            firing.update(on=False)
            await agg.poll_once()
            firing.update(on=True, since=456.0)
            await agg.poll_once()
            assert rec.captured_total == 2

            # kill the engine: two failed polls -> unreachable, and a
            # capture attributes the corpse with last-known payloads
            await eng_srv.close()
            await agg.poll_once()
            await agg.poll_once()
            assert agg.processes[eng_url].state == "unreachable"
            row = agg.capture(trigger="manual", force=True)
            assert row["attribution"]["process"] == eng_url
            assert row["attribution"]["phase"] == "down"
            bundle = rec.load(row["incident_id"])
            assert bundle["fleet"]["processes"][eng_url]["load"] \
                is not None
        finally:
            await agg.close()
            await rtr_srv.close()
    asyncio.run(body())


def test_aggregator_trace_cursor_rewinds_on_process_restart():
    """A process restarting on the same URL comes back with a fresh
    recorder (seq counter near zero); the aggregator must detect the
    regression and rewind its cursor, or it filters every new trace
    against the previous incarnation's watermark forever."""
    async def body():
        from tests.fake_engine import FakeEngine
        import aiohttp
        fake = FakeEngine(model="m", num_tokens=4)
        srv = TestServer(fake.build_app())
        await srv.start_server()
        url = f"http://127.0.0.1:{srv.port}"
        agg = FleetAggregator(routers=[], engines=[url],
                              poll_interval_s=30.0)
        await agg.start(poll=False)
        try:
            async def one():
                async with aiohttp.ClientSession() as session:
                    await session.post(
                        f"{url}/v1/chat/completions",
                        json={"model": "m",
                              "messages": [{"role": "user",
                                            "content": "x"}]})
            for _ in range(3):
                await one()
            await agg.poll_once()
            assert agg.processes[url].trace_cursor == 3
            # "restart": swap in a fresh recorder on the same URL
            from production_stack_tpu.tracing import TraceRecorder
            fake.tracer = TraceRecorder("fake-engine")
            await one()
            await agg.poll_once()     # detects last_seq 1 < cursor 3
            assert agg.processes[url].trace_cursor == 0
            await agg.poll_once()     # re-reads the new ring
            assert agg.processes[url].trace_cursor == 1
            assert agg.processes[url].traces_read == 4
        finally:
            await agg.close()
            await srv.close()
    asyncio.run(body())


def test_aggregator_trace_cursor_never_rereads(tmp_path):
    async def body():
        from tests.fake_engine import FakeEngine
        fake = FakeEngine(model="m", num_tokens=4)
        eng_srv = TestServer(fake.build_app())
        await eng_srv.start_server()
        eng_url = f"http://127.0.0.1:{eng_srv.port}"
        agg = FleetAggregator(routers=[], engines=[eng_url],
                              poll_interval_s=30.0)
        await agg.start(poll=False)
        try:
            async def one():
                from aiohttp.test_utils import TestClient
                # drive requests directly at the fake's app
                import aiohttp
                async with aiohttp.ClientSession() as session:
                    await session.post(
                        f"{eng_url}/v1/chat/completions",
                        json={"model": "m",
                              "messages": [{"role": "user",
                                            "content": "x"}]})
            await one()
            await agg.poll_once()
            assert agg.processes[eng_url].traces_read == 1
            await agg.poll_once()   # nothing new
            assert agg.processes[eng_url].traces_read == 1
            await one()
            await one()
            await agg.poll_once()
            assert agg.processes[eng_url].traces_read == 3
            assert agg.chains.traces_ingested == 3
        finally:
            await agg.close()
            await eng_srv.close()
    asyncio.run(body())


# ------------------------------------------------------------ app surface

def test_obsplane_app_surface(tmp_path):
    async def body():
        from aiohttp.test_utils import TestClient
        from production_stack_tpu.obsplane.app import (build_app,
                                                       parse_args)
        args = parse_args([
            "--routers", "http://127.0.0.1:1",   # unreachable: fine
            "--engines", "http://127.0.0.1:2",
            "--incident-dir", str(tmp_path / "incidents"),
            "--poll-interval", "30",
        ])
        client = TestClient(TestServer(build_app(args)))
        await client.start_server()
        try:
            r = await client.get("/health")
            assert r.status == 200
            body_ = await r.json()
            assert body_["processes"] == {"http://127.0.0.1:1":
                                          "pending",
                                          "http://127.0.0.1:2":
                                          "pending"}
            r = await client.get("/fleet")
            snap = await r.json()
            assert snap["chains"]["chains_complete"] == 0
            r = await client.get("/fleet/traces")
            assert (await r.json())["slowest"] == []
            r = await client.get("/fleet/incidents")
            assert (await r.json())["incidents"] == []
            r = await client.get("/fleet/incidents/nope")
            assert r.status == 404
            # manual capture always produces a bundle
            r = await client.post("/fleet/capture",
                                  json={"reason": "drill"})
            row = (await r.json())["captured"]
            assert row["trigger"] == "manual:drill"
            r = await client.get("/fleet/incidents")
            assert len((await r.json())["incidents"]) == 1
            r = await client.get("/metrics")
            text = await r.text()
            assert "tpu:fleet_processes" in text
            assert 'tpu:fleet_incidents_total{trigger="manual"} 1.0' \
                in text
        finally:
            await client.close()
    asyncio.run(body())


def test_obsplane_cli_requires_targets():
    from production_stack_tpu.obsplane.app import parse_args
    with pytest.raises(SystemExit):
        parse_args(["--poll-interval", "1"])
