"""Engine efficiency telemetry (ISSUE 11): window accounting units
with an injected clock, BlockManager fragmentation accounting, the
scrape-time delta sync, and the real-engine perf surfaces
(/load perf block, /debug/perf, xla_compile trace events).

Tiers:
- unit — EngineEffAccounting with ``now_fn`` injection (reconciliation
  math, ring-derived rates, compile event overlap) and BlockManager
  fragmentation counters (alloc-failure classification, occupancy
  observer, state census) — no engine, no device;
- metrics — EngineMetrics.sync_eff/sync_kvpool delta semantics and
  exposition names;
- engine — a real debug-tiny AsyncLLMEngine behind the aiohttp server
  launched WITHOUT warmup, so the first request's XLA compiles happen
  mid-request and must surface as counters, /debug/perf events, AND
  xla_compile spans on that request's trace.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.engine.block_manager import BlockManager
from production_stack_tpu.engine.efficiency import (EngineEffAccounting,
                                                    OCCUPANCY_BUCKETS)
from production_stack_tpu.engine.metrics import EngineMetrics
from production_stack_tpu.tracing import PhaseHistograms


# ------------------------------------------------------------ unit tier

class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_window_accounting_reconciles_with_injected_clock():
    """A steady synthetic stream of windows: kind totals must equal the
    independent token_steps_total, and the ring-derived rates must
    match hand-computed values at the injected timestamps."""
    clock = _Clock()
    acct = EngineEffAccounting(weight_bytes=1000, kv_position_bytes=10,
                               hbm_peak_bytes_per_s=1e6, now_fn=clock)
    # 10 windows, 1s apart: batch 4, 8 steps, 1 position; 2 live rows
    # emitting fully (16 real), 2 parked (16 pad), 0 dead
    for i in range(10):
        clock.t = float(i + 1)
        acct.note_window(steps=8, positions=1, batch=4, live_rows=2,
                         kv_len=100, real=16, pad=16, dead=0,
                         window_s=0.5)
    r = acct.report()
    dec = r["decode"]
    assert dec["real"] == 160 and dec["pad"] == 160
    assert dec["dead"] == 0
    assert dec["token_steps_total"] == 10 * 4 * 8
    assert dec["real"] + dec["pad"] + dec["dead"] == \
        dec["token_steps_total"]
    # per-window bytes: 8 * (1000 + 4*10*100) = 40000; half effective
    assert r["bytes_total"] == 10 * 8 * (1000 + 4000)
    assert r["bytes_effective"] == r["bytes_total"] // 2
    rates = acct.rates(horizon_s=10.0, now=10.0)
    # all 10 windows inside the horizon; 40000 bytes each, half live
    assert rates["total_bytes_per_s"] == pytest.approx(40000.0)
    assert rates["effective_bytes_per_s"] == pytest.approx(20000.0)
    assert rates["mbu_perc"] == pytest.approx(2.0)
    assert rates["live_fraction"] == pytest.approx(0.5)
    assert rates["decode_tokens_per_s"] == pytest.approx(16.0)
    # a narrower horizon sees only the last windows (cutoff is
    # inclusive: t in {5..10} = 6 windows over 5 seconds)
    rates5 = acct.rates(horizon_s=5.0, now=10.0)
    assert rates5["decode_tokens_per_s"] == pytest.approx(6 * 16 / 5.0)
    assert rates5["horizon_s"] == pytest.approx(5.0)


def test_window_accounting_speculative_positions_and_dead():
    """Speculative windows: positions = spec+1 per macro-step; rejected
    draft positions and finished tails land in dead, and the kinds
    still sum to the independent total."""
    acct = EngineEffAccounting(now_fn=_Clock(1.0))
    # batch 2, 4 macro-steps, 3 positions each; one live row emitted 7
    # tokens across its macro-steps, one row parked
    total = 2 * 4 * 3
    pad = 1 * 4 * 3
    real = 7
    dead = total - pad - real
    acct.note_window(steps=4, positions=3, batch=2, live_rows=1,
                     kv_len=64, real=real, pad=pad, dead=dead,
                     window_s=0.1)
    d = acct.report()["decode"]
    assert d["token_steps_total"] == total
    assert d["real"] + d["pad"] + d["dead"] == total
    assert d["dead"] == 5


def test_prefill_padding_accounting():
    acct = EngineEffAccounting(now_fn=_Clock(1.0))
    # bucket 64 over batch 8 = 512 positions; 100 real chunk tokens
    acct.note_prefill(bucket=64, batch=8, real_tokens=100)
    p = acct.report()["prefill"]
    assert p["real"] == 100 and p["pad"] == 412
    assert p["dispatches"] == 1


def test_compile_tracking_and_event_overlap():
    clock = _Clock(0.0)
    hist = PhaseHistograms(("kind", "window", "kv_bucket"),
                           buckets=(1.0, 10.0))
    acct = EngineEffAccounting(now_fn=clock, compile_hist=hist)
    acct.compile_started("decode", 8, 512, 4)
    assert acct.report()["compile_in_flight"] == 1
    acct.compile_finished("decode", 8, 512, started_at=5.0, dur_s=2.5,
                          batch=4)
    acct.compile_started("prefill", 64, 256, 8)
    acct.compile_finished("prefill", 64, 256, started_at=20.0,
                          dur_s=0.5, batch=8)
    r = acct.report()
    assert r["compile_in_flight"] == 0
    assert r["compiles_total"] == 2
    assert r["compiles"]["decode|8|512|4"]["count"] == 1
    assert r["compiles"]["decode|8|512|4"]["seconds"] == \
        pytest.approx(2.5)
    # duration histogram got both observations under their labels
    # (snapshot values are (cumulative buckets, sum, count))
    snap = hist.snapshot()
    assert snap[("decode", "8", "512")][1] == pytest.approx(2.5)
    assert snap[("decode", "8", "512")][2] == 1
    # overlap filter: [6.0, 7.0] overlaps the decode compile (5.0-7.5)
    # but not the prefill one (20.0-20.5)
    events = acct.compile_events_between(6.0, 7.0)
    assert [e[2] for e in events] == ["decode"]
    # an interval strictly between the two catches neither
    assert acct.compile_events_between(10.0, 19.0) == []
    # recent_compiles renders both
    assert len(acct.recent_compiles()) == 2


def test_window_ring_is_bounded():
    acct = EngineEffAccounting(ring_entries=8, now_fn=_Clock(1.0))
    for _ in range(50):
        acct.note_window(steps=1, positions=1, batch=1, live_rows=1,
                         kv_len=1, real=1, pad=0, dead=0,
                         window_s=0.01)
    assert len(acct.recent_windows(100)) == 8
    assert acct.report()["decode"]["windows"] == 50   # totals keep all


def test_rates_clamp_to_ring_coverage():
    """Regression: a busy engine whose ring evicts entries faster than
    the horizon drains must divide by the span the ring actually
    witnessed, not the full horizon — otherwise every rate understates
    by the eviction ratio."""
    clock = _Clock(0.0)
    acct = EngineEffAccounting(weight_bytes=0, kv_position_bytes=1,
                               ring_entries=4, now_fn=clock)
    # 20 windows, 0.1s apart: ring keeps only the last 4 (t=1.7..2.0)
    for i in range(20):
        clock.t = 0.1 * (i + 1)
        acct.note_window(steps=1, positions=1, batch=1, live_rows=1,
                         kv_len=1, real=10, pad=0, dead=0,
                         window_s=0.05)
    rates = acct.rates(horizon_s=10.0, now=2.0)
    # oldest resident entry is at t=1.7 -> 0.3s coverage holding 3
    # entries within (1.7, 2.0]... the t=1.7 entry itself is included
    # (cutoff inclusive): 4 entries * 10 real / 0.3s
    assert rates["decode_tokens_per_s"] == pytest.approx(40 / 0.3,
                                                         rel=1e-3)
    # an un-evicted ring still divides by uptime
    acct2 = EngineEffAccounting(ring_entries=100, now_fn=_Clock(0.0))
    acct2._started_at = 0.0
    acct2.note_window(steps=1, positions=1, batch=1, live_rows=1,
                      kv_len=1, real=10, pad=0, dead=0, window_s=0.05)
    assert acct2.rates(horizon_s=10.0,
                       now=2.0)["decode_tokens_per_s"] == \
        pytest.approx(5.0)


def test_window_accounting_variable_geometry():
    """Continuous batching across windows: consecutive windows change
    batch bucket AND window length; the kind totals must still equal
    the independent total and the byte-model rates stay finite."""
    clock = _Clock()
    acct = EngineEffAccounting(weight_bytes=500, kv_position_bytes=4,
                               hbm_peak_bytes_per_s=1e6, now_fn=clock)
    # (batch_bucket, steps, live_rows, real): a churny sequence —
    # bucket 8 full, bucket 4 with a finished tail, bucket 2 draining,
    # bucket 8 again after admissions, a 1-step mid-window-admission
    # window
    shapes = [(8, 8, 8, 64), (4, 8, 3, 20), (2, 4, 2, 8),
              (8, 2, 7, 14), (1, 1, 1, 1)]
    expect_total = 0
    expect_real = 0
    for i, (b, w, live, real) in enumerate(shapes):
        clock.t = float(i + 1)
        total = b * w
        pad = (b - live) * w
        dead = total - pad - real
        assert dead >= 0
        acct.note_window(steps=w, positions=1, batch=b, live_rows=live,
                         kv_len=128, real=real, pad=pad, dead=dead,
                         window_s=0.01 * w)
        expect_total += total
        expect_real += real
    d = acct.report()["decode"]
    assert d["token_steps_total"] == expect_total
    assert d["real"] + d["pad"] + d["dead"] == expect_total
    assert d["real"] == expect_real
    rates = acct.rates(horizon_s=10.0, now=5.0)
    for key in ("effective_bytes_per_s", "total_bytes_per_s",
                "mbu_perc", "decode_tokens_per_s"):
        v = rates[key]
        assert v >= 0 and v == v and v != float("inf"), (key, v)
    assert 0.0 < rates["live_fraction"] < 1.0
    # the ring keeps per-window geometry for /debug/perf diagnosis
    ring = acct.recent_windows(10)
    assert [(w["batch"], w["steps"], w["live_rows"]) for w in ring] == \
        [(b, w, l) for (b, w, l, _) in shapes]


def test_config_bucket_derivation_and_lookup():
    from production_stack_tpu.engine.config import EngineConfig
    cfg = EngineConfig(max_num_seqs=8, decode_window=8)
    assert cfg.window_adapt
    assert cfg.decode_batch_buckets == (1, 2, 4, 8)
    assert cfg.decode_window_buckets == (1, 2, 4, 8)
    assert cfg.batch_bucket_for(3) == 4
    assert cfg.batch_bucket_for(8) == 8
    assert cfg.batch_bucket_for(99) == 8      # clamped to the cap
    # non-power-of-two caps are always covered
    cfg6 = EngineConfig(max_num_seqs=6, decode_window=6)
    assert cfg6.decode_batch_buckets == (1, 2, 4, 6)
    # custom sets: filtered to range, cap appended when missing
    cfgc = EngineConfig(decode_batch_buckets=(2, 3, 99),
                        decode_window_buckets=(4,))
    assert cfgc.decode_batch_buckets == (2, 3, 8)
    assert cfgc.decode_window_buckets == (4, 8)
    with pytest.raises(ValueError):
        EngineConfig(decode_batch_buckets=(0, -3))
    # speculation pins fixed geometry: the spec executable only warms
    # at the full shape, so adaptation would compile mid-serving
    assert not EngineConfig(speculative_ngram_tokens=3).window_adapt


def test_non_hot_variant_pins_fixed_geometry():
    """A window needing an executable variant outside the warmed
    (greedy/plain) grid — here full-sort sampling via top_p < 1 —
    must dispatch at the FULL fixed geometry: that variant warms at
    the full shape only, and adapting it would compile a cold
    executable per geometry reached, mid-serving."""
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.scheduler import SamplingOptions
    cfg = EngineConfig(model="debug-tiny", max_model_len=128,
                       max_num_seqs=4, prefill_chunk=32,
                       prefill_buckets=(16, 32))
    eng = LLMEngine(cfg)
    eng.add_request(
        eng.tokenizer.encode("full sort variant pins geometry"),
        SamplingOptions(temperature=1.0, top_p=0.5, max_tokens=6,
                        ignore_eos=True), seq_id="s")
    for _ in range(200):
        if any(o.finished for o in eng.step()):
            break
    ring = eng.eff.recent_windows(50)
    assert ring, "no decode windows recorded"
    assert all(w["batch"] == cfg.max_num_seqs
               and w["steps"] == cfg.decode_window for w in ring), \
        [(w["batch"], w["steps"]) for w in ring]


def test_kv_bucket_above_grid_pins_fixed_geometry():
    """The warmup grid exists at the smallest kv bucket only: a
    window whose attention length lands in a LARGER bucket must
    dispatch at the full fixed geometry (one lazy compile per
    variant, the pre-r17 cost) instead of walking the adaptive grid
    cold at that bucket."""
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.scheduler import SamplingOptions
    cfg = EngineConfig(model="debug-tiny", max_model_len=256,
                       max_num_seqs=4, prefill_chunk=32,
                       prefill_buckets=(32, 64),
                       kv_len_buckets=(64, 256))
    eng = LLMEngine(cfg)
    # ~90-token prompt: every decode window's attention length sits
    # in the 256 bucket, above the warmed 64 bucket
    eng.add_request(
        eng.tokenizer.encode("kv bucket pin " * 7),
        SamplingOptions(temperature=0.0, max_tokens=6,
                        ignore_eos=True), seq_id="s")
    for _ in range(200):
        if any(o.finished for o in eng.step()):
            break
    ring = eng.eff.recent_windows(50)
    assert ring, "no decode windows recorded"
    assert all(w["kv_len"] == 256 and w["batch"] == cfg.max_num_seqs
               and w["steps"] == cfg.decode_window for w in ring), \
        [(w["kv_len"], w["batch"], w["steps"]) for w in ring]


def test_admission_imminent_respects_kv_gate():
    """The mid-window-admission lever must not fire when the last
    scheduler pass deferred the head waiter on the KV admission gate:
    a waiter + free slot does not mean the next pass admits, and
    shortening windows / pausing the pipeline under pool pressure
    costs fusion for nothing."""
    from production_stack_tpu.engine.scheduler import (Scheduler,
                                                       SamplingOptions,
                                                       Sequence)
    sched = Scheduler(max_num_seqs=2, max_model_len=64,
                      prefill_chunk=16)
    sched.add(Sequence("w1", list(range(4)), SamplingOptions()))
    admit = {"ok": False}
    sched.can_admit = lambda seq: admit["ok"]
    sched.schedule()
    assert sched.waiting and sched.free_slots and sched.kv_deferred
    admit["ok"] = True
    sched.schedule()
    assert not sched.kv_deferred and not sched.waiting


def test_engine_variable_geometry_reconciles_with_compaction():
    """A real (CPU, debug-tiny) engine through a churny composition:
    three rows with different budgets admitted together, so windows
    shrink as rows finish, the batch bucket steps down 4 -> 2 -> 1,
    and the survivors are COMPACTED into the low slots mid-stream —
    through all of it real+pad+dead must equal the independent total
    and real must equal exactly the decode-emitted tokens."""
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.scheduler import SamplingOptions
    cfg = EngineConfig(model="debug-tiny", max_model_len=128,
                       max_num_seqs=4, prefill_chunk=32,
                       prefill_buckets=(16, 32))
    eng = LLMEngine(cfg)
    budgets = {"a": 3, "b": 9, "c": 21}
    for name, mt in budgets.items():
        eng.add_request(
            eng.tokenizer.encode("variable geometry " + name * 3),
            SamplingOptions(temperature=0.0, max_tokens=mt,
                            ignore_eos=True), seq_id=name)
    done = set()
    slots_seen = set()
    for _ in range(400):
        for out in eng.step():
            if out.finished:
                done.add(out.seq_id)
        if "b" in done and "c" not in done:
            # only c remains: compaction must have packed it low
            slots_seen.add(eng.seqs["c"].slot)
        if len(done) == 3:
            break
    assert done == set(budgets)
    # c started at slot 2 (admission order) and must have been
    # remapped to slot 0 once a and b finished
    assert 0 in slots_seen
    for name, mt in budgets.items():
        assert len(eng.seqs[name].output_tokens) == mt
    rep = eng.eff.report()
    d = rep["decode"]
    assert d["token_steps_total"] > 0
    assert d["real"] + d["pad"] + d["dead"] == d["token_steps_total"]
    # decode-real = every emitted token minus the prefill-sampled first
    assert d["real"] == sum(budgets.values()) - len(budgets)
    ring = eng.eff.recent_windows(100)
    assert len({w["batch"] for w in ring}) >= 2, \
        "batch bucket never adapted"
    assert len({w["steps"] for w in ring}) >= 2, \
        "window length never adapted"
    assert all(w["batch"] >= w["live_rows"] for w in ring)
    rates = eng.eff.rates()
    assert rates["decode_tokens_per_s"] >= 0


# --------------------------------------------------- block manager tier

def test_block_manager_alloc_failure_classification():
    bm = BlockManager(num_blocks=5, block_size=4)   # 4 allocatable
    got = bm.alloc(3)
    assert got is not None and len(got) == 3
    # 1 free remains: asking for 2 is the fragmentation regime
    assert bm.alloc(2) is None
    assert bm.alloc_failures_fragmented == 1
    assert bm.alloc_failures_exhausted == 0
    # drain the pool: now a failure is true exhaustion
    assert bm.alloc(1) is not None
    assert bm.alloc(1) is None
    assert bm.alloc_failures_exhausted == 1
    # zero-block requests (fully prefix-shared prompts) are not
    # allocation attempts
    allocs_before = bm.allocs
    assert bm.alloc(0) == []
    assert bm.allocs == allocs_before
    assert bm.alloc(-1) is None
    report = bm.frag_report()
    assert report["alloc_failures_fragmented"] == 1
    assert report["alloc_failures_exhausted"] == 1
    assert report["blocks_allocated"] == 4


def test_block_manager_state_census_and_evictions():
    bm = BlockManager(num_blocks=5, block_size=2,
                      enable_prefix_caching=True)
    blocks = bm.alloc(2)
    assert bm.frag_report()["active"] == 2
    assert bm.frag_report()["free"] == 2
    # register + free: the blocks become evictable cache, not free
    tokens = [1, 2, 3, 4]
    assert bm.register(tokens, blocks) == 2
    bm.free(blocks)
    rep = bm.frag_report()
    assert rep["active"] == 0 and rep["cached"] == 2 and rep["free"] == 2
    # allocating past the free list reclaims cached blocks (LRU) and
    # counts the evictions
    got = bm.alloc(4)
    assert got is not None and len(got) == 4
    assert bm.cache_evictions == 2
    assert bm.frag_report()["cached"] == 0


def test_block_manager_occupancy_observer():
    seen = []
    bm = BlockManager(num_blocks=5, block_size=4)
    bm.on_alloc_occupancy = seen.append
    bm.alloc(2)          # observed at usage 0.0
    bm.alloc(2)          # observed at usage 0.5
    bm.alloc(1)          # observed at usage 1.0 (fails, still observed)
    assert seen == [0.0, 0.5, 1.0]
    # the metrics layer's histogram shape accepts these observations
    hist = PhaseHistograms((), buckets=OCCUPANCY_BUCKETS)
    for v in seen:
        hist.observe(v)
    (cum, total, n), = hist.snapshot().values()
    assert n == 3 and total == pytest.approx(1.5)


# -------------------------------------------------------- metrics tier

def test_metrics_delta_sync_eff_and_kvpool():
    m = EngineMetrics(model="t")
    acct = EngineEffAccounting(hbm_peak_bytes_per_s=1e9,
                               weight_bytes=100,
                               kv_position_bytes=1,
                               now_fn=_Clock(1.0))
    acct.note_window(steps=4, positions=1, batch=2, live_rows=1,
                     kv_len=8, real=4, pad=4, dead=0, window_s=0.1)
    m.sync_eff(acct.report(), acct.rates(now=1.0))
    m.sync_eff(acct.report(), acct.rates(now=1.0))   # idempotent resync
    text = m.render().decode()
    assert 'tpu:engine_token_steps_total{kind="real",model_name="t",' \
           'phase="decode"} 4.0' in text
    assert 'tpu:engine_token_steps_total{kind="pad",model_name="t",' \
           'phase="decode"} 4.0' in text
    # a second window advances counters by the delta only
    acct.note_window(steps=4, positions=1, batch=2, live_rows=1,
                     kv_len=8, real=3, pad=4, dead=1, window_s=0.1)
    m.sync_eff(acct.report(), acct.rates(now=1.0))
    text = m.render().decode()
    assert 'kind="real",model_name="t",phase="decode"} 7.0' in text
    assert 'kind="dead",model_name="t",phase="decode"} 1.0' in text
    bm = BlockManager(num_blocks=5, block_size=4)
    bm.alloc(4)
    bm.alloc(1)
    m.sync_kvpool(bm.frag_report())
    m.sync_kvpool(bm.frag_report())
    text = m.render().decode()
    assert 'tpu:kvpool_blocks{model_name="t",state="active"} 4.0' in text
    assert 'tpu:kvpool_alloc_failures_total{model_name="t",' \
           'reason="exhausted"} 1.0' in text
    assert "tpu:engine_mbu_perc" in text
    assert "tpu:decode_window_live_fraction" in text
    assert "tpu:engine_compile_seconds" in text
    assert "tpu:kvpool_alloc_occupancy" in text


# --------------------------------------------------------- engine tier

@pytest.fixture(scope="module")
def cold_engine():
    """A real debug-tiny engine with NO warmup: the first request's
    XLA compiles happen mid-request, which is exactly what the compile
    observability must make visible."""
    from production_stack_tpu.engine.async_engine import AsyncLLMEngine
    from production_stack_tpu.engine.config import EngineConfig
    cfg = EngineConfig(model="debug-tiny", max_model_len=128,
                       max_num_seqs=2, prefill_chunk=32,
                       prefill_buckets=(16, 32))
    return AsyncLLMEngine(cfg)


def _with_client(engine, coro, **build_kw):
    from production_stack_tpu.engine.server import build_app

    async def runner():
        app = build_app(engine, **build_kw)
        async with TestClient(TestServer(app)) as client:
            return await coro(client)
    return asyncio.run(runner())


def test_engine_perf_surfaces_and_compile_trace(cold_engine):
    async def body(client):
        body = {"model": "debug-tiny",
                "messages": [{"role": "user", "content": "measure me"}],
                "max_tokens": 6, "temperature": 0.0,
                "ignore_eos": True}
        r = await client.post("/v1/chat/completions", json=body)
        assert r.status == 200
        trace_id = r.headers["x-trace-id"]
        # /load perf block: the request's decode steps are accounted
        r = await client.get("/load")
        perf = (await r.json())["perf"]
        steps = perf["token_steps"]
        assert steps["real"] == 5          # 6 tokens, first = prefill
        assert steps["token_steps_total"] == \
            steps["real"] + steps["pad"] + steps["dead"]
        assert perf["compiles_total"] >= 2   # cold start compiled
        assert perf["compile_in_flight"] == 0
        assert perf["weight_bytes"] > 0
        # /debug/perf: window ring + compile events + pool census
        r = await client.get("/debug/perf?limit=5")
        assert r.status == 200
        dp = await r.json()
        assert dp["windows"], "no window breakdowns recorded"
        w = dp["windows"][-1]
        # adaptive dispatch: one live row -> batch bucket 1 (not the
        # configured max_num_seqs=2); the 5-step decode budget walks a
        # 4-step window then a final 1-step one (the dead-budget cap
        # rejects the 8 bucket: a 3-step tail on one live row)
        assert w["batch"] == 1 and w["steps"] == 1
        assert [x["steps"] for x in dp["windows"][-2:]] == [4, 1]
        assert {"real", "pad", "dead", "kv_len", "live_rows",
                "window_s"} <= set(w)
        kinds = [e["kind"] for e in dp["compiles"]]
        assert "decode" in kinds and "prefill" in kinds
        # compile events carry the dispatched batch bucket
        assert all("batch" in e for e in dp["compiles"])
        assert dp["kv_pool"]["active"] == 0   # request finished
        assert dp["totals"]["compiles_total"] == len(dp["compiles"])
        # the compile-stalled request's trace carries xla_compile
        # events (the compiles overlapped its life)
        r = await client.get(f"/debug/traces?trace_id={trace_id}")
        traces = (await r.json())["traces"]
        assert traces
        compile_spans = [s for s in traces[0]["spans"]
                         if s["name"] == "xla_compile"]
        assert compile_spans, "cold-start compiles missing from trace"
        assert compile_spans[0]["kind"] == "event"
        assert "kind" in compile_spans[0]["attrs"]
        # /metrics exposition carries the new families with live values
        r = await client.get("/metrics")
        text = (await r.read()).decode()
        assert 'tpu:engine_token_steps_total{kind="real"' in text
        assert "tpu:engine_compiles_total{" in text
        assert "tpu:engine_compile_seconds_bucket" in text
        assert "tpu:kvpool_blocks{" in text
    _with_client(cold_engine, body)


def test_debug_perf_behind_api_key(cold_engine):
    """/debug/perf follows /debug/traces' auth posture: enforced when
    an API key is configured (probe endpoints stay open)."""
    async def body(client):
        r = await client.get("/debug/perf")
        assert r.status == 401
        r = await client.get("/debug/perf",
                             headers={"Authorization": "Bearer sk"})
        assert r.status == 200
        r = await client.get("/load")   # probe surface stays open
        assert r.status == 200
        assert "perf" in await r.json()
    _with_client(cold_engine, body, api_key="sk")


def test_ring_entries_carry_wall_clock_stamps():
    """Window and compile ring entries are stamped with ``at_unix``
    (wall clock) alongside the monotonic ``at`` — the obsplane flight
    recorder aligns engine rings with trace spans across processes,
    which monotonic stamps (per-process epoch) cannot do."""
    wall = _Clock(1000.0)
    acct = EngineEffAccounting(now_fn=_Clock(5.0), wall_fn=wall)
    acct.note_window(steps=2, positions=1, batch=4, live_rows=3,
                     kv_len=256, real=6, pad=2, dead=0, window_s=0.1)
    entry = acct.recent_windows(1)[0]
    assert entry["at_unix"] == pytest.approx(1000.0)
    assert entry["at"] == pytest.approx(5.0)
    acct.compile_started("decode", 8, 512, 4)
    acct.compile_finished("decode", 8, 512, started_at=5.0, dur_s=2.0,
                          batch=4)
    row = acct.recent_compiles(1)[0]
    # wall stamp of the compile START: wall-at-finish minus duration
    assert row["at_unix"] == pytest.approx(998.0)
    assert row["duration_s"] == pytest.approx(2.0)
    # the trace-seal hook keeps its 6-tuple shape (server.py unpacks)
    events = acct.compile_events_between(5.5, 6.0)
    assert len(events) == 1 and len(events[0]) == 6
